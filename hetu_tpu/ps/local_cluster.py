"""Self-provisioned local PS cluster (scheduler + N servers as spawned
processes, the calling process becomes worker 0).

One shared implementation of the bootstrap that bench.py and the examples
need when run standalone — outside a ``heturun`` launch (reference: the
``tests/*.sh`` scripts' local mpirun clusters). The test suite's
``tests/test_ps.run_cluster`` stays separate: it additionally runs worker
BODIES in subprocesses and collects per-worker results, which this helper
deliberately does not (the caller IS the worker).
"""
from __future__ import annotations

import contextlib
import multiprocessing
import os
import socket
import shutil
import tempfile
import time


def _ps_env(port: int, n_workers: int, n_servers: int) -> dict:
    return {"DMLC_PS_ROOT_URI": "127.0.0.1",
            "DMLC_PS_ROOT_PORT": str(port),
            "DMLC_NUM_WORKER": str(n_workers),
            "DMLC_NUM_SERVER": str(n_servers)}


def _sched_proc(port, n_workers, n_servers):
    os.environ.update(_ps_env(port, n_workers, n_servers))
    os.environ["DMLC_ROLE"] = "scheduler"
    from . import server as srv
    srv.start_scheduler_from_env()
    srv.scheduler_wait()
    srv.stop_scheduler()


def _server_proc(port, n_workers, n_servers, idx, stopfile):
    os.environ.update(_ps_env(port, n_workers, n_servers))
    os.environ.update({"DMLC_ROLE": "server", "SERVER_ID": str(idx),
                       "DMLC_PS_SERVER_URI": "127.0.0.1",
                       # port 0: bind an OS-assigned port, registered with
                       # the scheduler (race-free, commit 5eca2ab)
                       "DMLC_PS_SERVER_PORT": "0"})
    from . import server as srv
    srv.start_server_from_env()
    while not os.path.exists(stopfile):
        time.sleep(0.05)
    srv.stop_server()


@contextlib.contextmanager
def local_cluster(n_servers: int = 1, n_workers: int = 1, port: int = None):
    """Spawn scheduler + servers, set THIS process up as worker 0, yield.
    On exit, signal the servers to stop and reap every process."""
    if port is None:
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
    # signal-by-creation file inside a fresh private dir (mktemp is
    # race-prone: the generated name can be claimed by another process)
    stopdir = tempfile.mkdtemp(prefix="hetu_ps_stop_")
    stopfile = os.path.join(stopdir, "stop")
    ctx = multiprocessing.get_context("spawn")
    procs = [ctx.Process(target=_sched_proc,
                         args=(port, n_workers, n_servers))]
    procs += [ctx.Process(target=_server_proc,
                          args=(port, n_workers, n_servers, i, stopfile))
              for i in range(n_servers)]
    for p in procs:
        p.start()
    os.environ.update(_ps_env(port, n_workers, n_servers))
    os.environ.update({"DMLC_ROLE": "worker", "WORKER_ID": "0"})
    try:
        yield port
    finally:
        with open(stopfile, "w") as f:
            f.write("stop")
        for p in procs:
            p.join(timeout=15)
        for p in procs:
            if p.is_alive():
                p.terminate()
        shutil.rmtree(stopdir, ignore_errors=True)
