"""Self-provisioned local PS cluster (scheduler + N servers as spawned
processes, the calling process becomes worker 0).

One shared implementation of the bootstrap that bench.py and the examples
need when run standalone — outside a ``heturun`` launch (reference: the
``tests/*.sh`` scripts' local mpirun clusters). The test suite's
``tests/test_ps.run_cluster`` stays separate: it additionally runs worker
BODIES in subprocesses and collects per-worker results, which this helper
deliberately does not (the caller IS the worker).
"""
from __future__ import annotations

import contextlib
import os
import socket
import shutil
import subprocess
import sys
import tempfile

_LIGHT_MAIN = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "_light_main.py")


def _ps_env(port: int, n_workers: int, n_servers: int) -> dict:
    return {"DMLC_PS_ROOT_URI": "127.0.0.1",
            "DMLC_PS_ROOT_PORT": str(port),
            "DMLC_NUM_WORKER": str(n_workers),
            "DMLC_NUM_SERVER": str(n_servers)}


def spawn_light_role(role: str, env_extra: dict) -> subprocess.Popen:
    """Launch a scheduler/server as a LIGHT process: ``_light_main.py``
    executed by file path needs only ctypes + the prebuilt lib — no
    hetu_tpu/jax import (seconds per process saved at every cluster
    bootstrap). Shared by this module and tests/test_ps.run_cluster."""
    from ..csrc.build import build
    env = os.environ.copy()
    env.update(env_extra)
    env["DMLC_ROLE"] = role
    env["HETU_PS_LIB"] = build("libhetu_ps.so")
    return subprocess.Popen([sys.executable, _LIGHT_MAIN], env=env)


def spawn_light_server(idx: int, base_env: dict, stopfile: str,
                       port: str = "0") -> subprocess.Popen:
    """Server-role wrapper over ``spawn_light_role`` carrying the full
    bootstrap contract in ONE place (``_light_main.py`` hard-fails on a
    missing key). ``port="0"``: bind an OS-assigned port, registered with
    the scheduler (race-free)."""
    return spawn_light_role("server", {**base_env, "SERVER_ID": str(idx),
                                       "DMLC_PS_SERVER_URI": "127.0.0.1",
                                       "DMLC_PS_SERVER_PORT": port,
                                       "HETU_PS_STOPFILE": stopfile})


def reap_light_procs(procs, timeout: float = 15.0):
    """Wait for light children; SIGKILL stragglers AND reap them (a kill
    without a wait leaves a zombie for the rest of the session)."""
    for p in procs:
        try:
            p.wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            p.kill()
            p.wait()


def resolve_test_kill_index(n_servers: int):
    """The ``HETU_PS_TEST_KILL_SERVER`` fault hook's gate + bounds check.

    Follows the resilience fault-injection convention (HETU_FAULT_SPEC):
    destructive test hooks are INERT unless ``HETU_TEST_MODE`` is explicitly
    truthy, so an env var leaked from a test session cannot SIGKILL a real
    server. In test mode an out-of-range index is a hard error — silently
    killing the wrong process (or IndexError-ing into the scheduler slot)
    would make the fault test meaningless."""
    from ..resilience import test_mode_enabled
    raw = os.environ.get("HETU_PS_TEST_KILL_SERVER")
    if raw is None or not test_mode_enabled():
        return None
    idx = int(raw)
    if not 0 <= idx < n_servers:
        raise ValueError(
            f"HETU_PS_TEST_KILL_SERVER={raw} out of range for "
            f"{n_servers} servers")
    return idx


@contextlib.contextmanager
def local_cluster(n_servers: int = 1, n_workers: int = 1, port: int = None):
    """Spawn scheduler + servers, set THIS process up as worker 0, yield.
    On exit, signal the servers to stop and reap every process."""
    if port is None:
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
    # signal-by-creation file inside a fresh private dir (mktemp is
    # race-prone: the generated name can be claimed by another process)
    stopdir = tempfile.mkdtemp(prefix="hetu_ps_stop_")
    stopfile = os.path.join(stopdir, "stop")
    base = _ps_env(port, n_workers, n_servers)
    procs = []
    try:
        # spawn INSIDE the try: if a later spawn fails, the finally still
        # signals and reaps the children already running
        procs.append(spawn_light_role("scheduler", base))
        procs += [spawn_light_server(i, base, stopfile)
                  for i in range(n_servers)]
        # fault-injection hook (bench hang-proofing tests): SIGKILL server
        # <idx> right after spawn, so the caller's RPCs face a cluster
        # that can never complete registration. The section-subprocess
        # group-kill is the only thing standing between this and a hung
        # bench cell — tests/test_bench_driver.py pins that it holds.
        # Gated on HETU_TEST_MODE + bounds-checked (resolve_test_kill_index).
        kill_idx = resolve_test_kill_index(n_servers)
        if kill_idx is not None:
            victim = procs[1 + kill_idx]
            victim.kill()
            victim.wait()
        os.environ.update(base)
        os.environ.update({"DMLC_ROLE": "worker", "WORKER_ID": "0"})
        yield port
    finally:
        with open(stopfile, "w") as f:
            f.write("stop")
        reap_light_procs(procs)
        shutil.rmtree(stopdir, ignore_errors=True)
