"""Self-provisioned local PS cluster (scheduler + N servers as spawned
processes, the calling process becomes worker 0).

One shared implementation of the bootstrap that bench.py and the examples
need when run standalone — outside a ``heturun`` launch (reference: the
``tests/*.sh`` scripts' local mpirun clusters). The test suite's
``tests/test_ps.run_cluster`` stays separate: it additionally runs worker
BODIES in subprocesses and collects per-worker results, which this helper
deliberately does not (the caller IS the worker).
"""
from __future__ import annotations

import contextlib
import os
import socket
import shutil
import subprocess
import sys
import tempfile
import time

_LIGHT_MAIN = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "_light_main.py")

# The live cluster registry: the `ps_kill` fault-injection kind
# (resilience.FaultInjector) and tests resolve the CURRENT server process
# for a given id here. Only one local_cluster is live per process.
_LIVE: dict = {}


def _ps_env(port: int, n_workers: int, n_servers: int) -> dict:
    return {"DMLC_PS_ROOT_URI": "127.0.0.1",
            "DMLC_PS_ROOT_PORT": str(port),
            "DMLC_NUM_WORKER": str(n_workers),
            "DMLC_NUM_SERVER": str(n_servers)}


def spawn_light_role(role: str, env_extra: dict) -> subprocess.Popen:
    """Launch a scheduler/server as a LIGHT process: ``_light_main.py``
    executed by file path needs only ctypes + the prebuilt lib — no
    hetu_tpu/jax import (seconds per process saved at every cluster
    bootstrap). Shared by this module and tests/test_ps.run_cluster."""
    from ..csrc.build import build
    env = os.environ.copy()
    env.update(env_extra)
    env["DMLC_ROLE"] = role
    env["HETU_PS_LIB"] = build("libhetu_ps.so")
    return subprocess.Popen([sys.executable, _LIGHT_MAIN], env=env)


def spawn_light_server(idx: int, base_env: dict, stopfile: str,
                       port: str = "0") -> subprocess.Popen:
    """Server-role wrapper over ``spawn_light_role`` carrying the full
    bootstrap contract in ONE place (``_light_main.py`` hard-fails on a
    missing key). ``port="0"``: bind an OS-assigned port, registered with
    the scheduler (race-free)."""
    return spawn_light_role("server", {**base_env, "SERVER_ID": str(idx),
                                       "DMLC_PS_SERVER_URI": "127.0.0.1",
                                       "DMLC_PS_SERVER_PORT": port,
                                       "HETU_PS_STOPFILE": stopfile})


def reap_light_procs(procs, timeout: float = 15.0):
    """Wait for light children; SIGKILL stragglers AND reap them (a kill
    without a wait leaves a zombie for the rest of the session).

    ``timeout`` is ONE shared deadline across all children, not a per-child
    budget: a wedged cluster of N processes tears down in bounded total
    time instead of N x timeout."""
    deadline = time.monotonic() + timeout
    for p in procs:
        try:
            p.wait(timeout=max(0.0, deadline - time.monotonic()))
        except subprocess.TimeoutExpired:
            p.kill()
            p.wait()


def resolve_test_kill_index(n_servers: int):
    """The ``HETU_PS_TEST_KILL_SERVER`` fault hook's gate + bounds check.

    Follows the resilience fault-injection convention (HETU_FAULT_SPEC):
    destructive test hooks are INERT unless ``HETU_TEST_MODE`` is explicitly
    truthy, so an env var leaked from a test session cannot SIGKILL a real
    server. In test mode an out-of-range index is a hard error — silently
    killing the wrong process (or IndexError-ing into the scheduler slot)
    would make the fault test meaningless."""
    from ..resilience import test_mode_enabled
    raw = os.environ.get("HETU_PS_TEST_KILL_SERVER")
    if raw is None or not test_mode_enabled():
        return None
    idx = int(raw)
    if not 0 <= idx < n_servers:
        raise ValueError(
            f"HETU_PS_TEST_KILL_SERVER={raw} out of range for "
            f"{n_servers} servers")
    return idx


def kill_live_server(idx: int):
    """SIGKILL the CURRENT process serving server id ``idx`` of the live
    ``local_cluster`` — the executor of the ``ps_kill@step[:idx]`` fault
    kind. Test-gating lives in the FaultInjector (HETU_TEST_MODE); here the
    index is bounds-checked like ``resolve_test_kill_index`` so the fault
    can never land on the scheduler or a random child."""
    if not _LIVE:
        raise RuntimeError("ps_kill: no live local_cluster in this process")
    n = _LIVE["n_servers"]
    if not 0 <= idx < n:
        raise ValueError(f"ps_kill server index {idx} out of range for "
                         f"{n} servers")
    victim = _LIVE["servers"][idx]
    victim.kill()
    victim.wait()


def get_live_cluster() -> dict:
    """The live cluster registry (empty when none): n_servers, servers
    (id -> current Popen), supervisor (PSSupervisor or None), snapshot_dir,
    port."""
    return _LIVE


@contextlib.contextmanager
def local_cluster(n_servers: int = 1, n_workers: int = 1, port: int = None,
                  *, ha: bool = False, snapshot_ms: int = 1000,
                  max_respawns: int = 3, snapshot_dir: str = None,
                  failover_ms: int = 30000):
    """Spawn scheduler + servers, set THIS process up as worker 0, yield.
    On exit, signal the servers to stop and reap every process.

    ``ha=True`` turns on the full high-availability stack: servers write
    continuous shard snapshots (``snapshot_ms``), a :class:`PSSupervisor`
    respawns dead servers from the freshest snapshot (at most
    ``max_respawns`` times), and this worker blocks-with-deadline through a
    server death instead of raising (``failover_ms`` →
    DMLC_PS_FAILOVER_DEADLINE_MS, set only if not already in the env).
    """
    if port is None:
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
    # signal-by-creation file inside a fresh private dir (mktemp is
    # race-prone: the generated name can be claimed by another process)
    stopdir = tempfile.mkdtemp(prefix="hetu_ps_stop_")
    stopfile = os.path.join(stopdir, "stop")
    base = _ps_env(port, n_workers, n_servers)
    snapdir = None
    saved_env: dict = {}
    if ha:
        snapdir = snapshot_dir or tempfile.mkdtemp(prefix="hetu_ps_snap_")
        base.update({"DMLC_PS_SNAPSHOT_DIR": snapdir,
                     "DMLC_PS_SNAPSHOT_MS": str(int(snapshot_ms))})
        # these ride into os.environ below (os.environ.update(base)); a
        # leaked snapshot knob would make a LATER non-HA cluster's servers
        # snapshot into a deleted tempdir forever — remember the caller's
        # values (or their absence) to undo on exit
        saved_env = {k: os.environ.get(k)
                     for k in ("DMLC_PS_SNAPSHOT_DIR",
                               "DMLC_PS_SNAPSHOT_MS")}
    procs = []
    servers_by_id: dict = {}
    sup = None
    failover_env_set = False
    try:
        # spawn INSIDE the try: if a later spawn fails, the finally still
        # signals and reaps the children already running
        procs.append(spawn_light_role("scheduler", base))
        for i in range(n_servers):
            servers_by_id[i] = spawn_light_server(i, base, stopfile)
            procs.append(servers_by_id[i])
        # fault-injection hook (bench hang-proofing tests): SIGKILL server
        # <idx> right after spawn, so the caller's RPCs face a cluster
        # that can never complete registration. The section-subprocess
        # group-kill is the only thing standing between this and a hung
        # bench cell — tests/test_bench_driver.py pins that it holds.
        # Gated on HETU_TEST_MODE + bounds-checked (resolve_test_kill_index).
        kill_idx = resolve_test_kill_index(n_servers)
        if kill_idx is not None:
            victim = servers_by_id[kill_idx]
            victim.kill()
            victim.wait()
        if ha:
            from .supervisor import PSSupervisor

            def _respawn(i):
                p = spawn_light_server(
                    i, {**base, "DMLC_PS_RESTORE_DIR": snapdir}, stopfile)
                servers_by_id[i] = p
                procs.append(p)  # teardown reaps replacements too
                return p

            # procs is held by reference: ps_kill's victim and the
            # supervisor's wedged-process check stay in sync
            sup = PSSupervisor("127.0.0.1", port, n_servers, _respawn,
                               procs=servers_by_id,
                               max_respawns=max_respawns)
            sup.start()
            if "DMLC_PS_FAILOVER_DEADLINE_MS" not in os.environ:
                # this worker opts into failover for THIS cluster only — a
                # leaked deadline would turn a later non-HA cluster's fast
                # server-death error into a silent block-with-deadline
                os.environ["DMLC_PS_FAILOVER_DEADLINE_MS"] = \
                    str(int(failover_ms))
                failover_env_set = True
        os.environ.update(base)
        os.environ.update({"DMLC_ROLE": "worker", "WORKER_ID": "0"})
        _LIVE.clear()
        _LIVE.update({"n_servers": n_servers, "servers": servers_by_id,
                      "supervisor": sup, "snapshot_dir": snapdir,
                      "port": port,
                      # hetu-elastic (elastic.grow_local_cluster_server):
                      # enough to spawn a JOINING server into this world
                      # and have teardown reap it
                      "base_env": dict(base), "stopfile": stopfile,
                      "procs": procs})
        yield port
    finally:
        _LIVE.clear()
        if failover_env_set:
            os.environ.pop("DMLC_PS_FAILOVER_DEADLINE_MS", None)
        for k, v in saved_env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        if sup is not None:
            sup.stop()  # before the stopfile: clean exits are not "deaths"
        with open(stopfile, "w") as f:
            f.write("stop")
        reap_light_procs(procs)
        shutil.rmtree(stopdir, ignore_errors=True)
        if ha and snapshot_dir is None and snapdir:
            shutil.rmtree(snapdir, ignore_errors=True)
