"""Worker-side PS client: ctypes facade over the native agent.

Capability parity with the reference's worker usage of ``libps.so``
(gpu_ops/executor.py:69-100 loads the lib; tests/pstests/test_apis.py and
ParameterServerCommunicate.py call InitTensor/Push/Pull/SparsePush/.../Wait/
BarrierWorker on it directly). The reference hands ctypes the DLArray struct
pointer; here arrays cross as numpy buffers (the TPU NDArray is a jax.Array,
so device values stage through host numpy — the analogue of the reference's
d2h staging in ParameterServerCommunicate.py:29-36).
"""
from __future__ import annotations

import ctypes
import os
import sys

import numpy as np

from . import wire_constants as wire
from ..csrc.build import build

_f32p = ctypes.POINTER(ctypes.c_float)
_i64p = ctypes.POINTER(ctypes.c_long)
_u64p = ctypes.POINTER(ctypes.c_ulonglong)

_INIT_TYPE = {"constant": 0, "uniform": 1, "normal": 2, "truncated_normal": 3}
_OPT_TYPE = {"sgd": 0, "momentum": 1, "nesterov": 2, "adagrad": 3, "adam": 4}


def _load_lib():
    lib = ctypes.CDLL(build("libhetu_ps.so"))
    lib.LastError.restype = ctypes.c_char_p
    lib.getLoads.restype = ctypes.c_char_p
    lib.PushData.restype = ctypes.c_long
    lib.PullData.restype = ctypes.c_long
    lib.SetPushOpts.argtypes = [ctypes.c_int, ctypes.c_float, ctypes.c_float,
                                ctypes.c_float]
    lib.rank.restype = ctypes.c_int
    lib.nrank.restype = ctypes.c_int
    lib.num_servers.restype = ctypes.c_int
    lib.SetWorldVersion.argtypes = [ctypes.c_ulonglong]
    lib.GetWorldVersion.restype = ctypes.c_ulonglong
    lib.RefreshServers.restype = ctypes.c_int
    lib.SetTrailStep.argtypes = [ctypes.c_longlong]
    lib.DrainTrailSpans.restype = ctypes.c_long
    lib.TrailDropped.restype = ctypes.c_longlong
    lib.SetChaos.argtypes = [ctypes.c_char_p]
    lib.DrainChaosEvents.restype = ctypes.c_long
    lib.ServerSnapshotNow.argtypes = [ctypes.c_int, ctypes.c_longlong,
                                      _i64p, ctypes.c_int]
    return lib


def _as_f32(arr) -> np.ndarray:
    if hasattr(arr, "asnumpy"):  # NDArray
        arr = arr.asnumpy()
    return np.ascontiguousarray(arr, dtype=np.float32)


def _as_i64(arr) -> np.ndarray:
    if hasattr(arr, "asnumpy"):
        arr = arr.asnumpy()
    return np.ascontiguousarray(arr, dtype=np.int64)


class PSClient:
    """One per worker process. Methods mirror the reference C API names."""

    def __init__(self):
        self._lib = _load_lib()
        self._lib.Init()
        self._check()
        # pinned staging buffers per tensor id: async Push/Pull contract
        # requires buffers to stay alive until Wait
        self._staging: dict[int, list] = {}
        self._trail_buf = None   # reused drain buffer (DrainTrailSpans)
        # register as the process-wide worker communicator so components that
        # resolve it via ht.get_worker_communicate() (e.g. CacheSparseTable)
        # find this agent regardless of how it was constructed
        from . import _register_worker
        _register_worker(self)

    @classmethod
    def from_env(cls) -> "PSClient":
        return cls()

    def _check(self):
        err = self._lib.LastError()
        if err:
            raise RuntimeError(err.decode())

    def _stage(self, node: int, *arrays):
        self._staging.setdefault(node, []).extend(arrays)

    # -- lifecycle ----------------------------------------------------------
    def close(self, *, raise_on_error: bool | None = None):
        """Finalize the native agent, SURFACING teardown errors: Finalize's
        guard stashes them in LastError, and silently swallowing them hid
        real socket/teardown bugs. Raising is wrong in three situations —
        interpreter shutdown (atexit / GC-driven closes, where it would
        mask the process's real exit), an exception already propagating
        (``finally:``-driven closes, where it would replace the real
        failure), and the ``worker_finish`` teardown path (where a benign
        teardown-window socket error would convert a successful worker run
        into a nonzero exit and burn the supervisor's restart budget) —
        there the error is logged instead. ``raise_on_error`` forces the
        choice; ``None`` auto-detects the first two."""
        self._lib.Finalize()
        err = self._lib.LastError()
        if err:
            msg = f"PS Finalize failed: {err.decode()}"
            if raise_on_error is None:
                raise_on_error = (not sys.is_finalizing()
                                  and sys.exc_info()[0] is None)
            if raise_on_error:
                raise RuntimeError(msg)
            print(msg, file=sys.stderr)

    Finalize = close

    @property
    def rank(self) -> int:
        return self._lib.rank()

    @property
    def nrank(self) -> int:
        return self._lib.nrank()

    @property
    def num_servers(self) -> int:
        return self._lib.num_servers()

    def ServerStats(self, server: int) -> dict:
        """Per-server HA + health counters (rides the fast channel):
        ``updates`` applied since start/restore, ``snapshot_updates``
        covered by the latest complete snapshot, ``restored_updates`` the
        counter the server restored from (-1 = fresh start),
        ``snapshot_version``, ``n_params``; plus the telemetry extension —
        ``requests`` served, ``apply_ms_avg`` (mean wall ms per applied
        write), ``snapshot_age_ms`` since THIS incarnation's latest
        snapshot (-1 = none yet, including right after a restore), and
        ``dedup_clients`` (resend-dedup ledger occupancy), and
        ``crc_rejects`` (requests rejected before any apply because their
        payload CRC32C did not verify — docs/FAULT_TOLERANCE.md "Chaos
        testing & transport hardening"). After a recovery,
        ``acked-before-death updates - restored_updates`` is exactly how
        many updates that shard lost."""
        out = np.zeros(wire.SERVER_STATS_SLOTS, np.int64)
        self._lib.QueryServerStats(ctypes.c_int(int(server)),
                                   out.ctypes.data_as(_i64p),
                                   ctypes.c_int(wire.SERVER_STATS_SLOTS))
        self._check()
        raw = wire.unpack_fields(wire.SERVER_STATS_FIELDS, out)
        # surface the apply latency as a derived mean, not raw ns slots
        apply_ns, apply_cnt = raw.pop("apply_ns"), raw.pop("apply_count")
        raw["apply_ms_avg"] = (round(apply_ns / apply_cnt / 1e6, 6)
                               if apply_cnt else None)
        return raw

    def ClientStats(self) -> dict:
        """This worker's RPC counters: round trips issued, fast-retry
        attempts, successful failover re-issues, the hetuq raw-vs-wire
        byte counters over every quantizable value payload (pushes and pull
        responses; with quantization off raw == wire, so an off-vs-int8 A/B
        reads its compression ratio straight from here — worker.h
        client_stats, docs/COMM_QUANT.md), plus the hetuchaos hardening
        counters: ``timeouts`` (recv/deadline expiries), ``backoff_ms``
        (total retry backoff slept), ``crc_rejects`` (server CRC
        rejections observed + local response-verify failures),
        ``chaos_faults`` (injected by an armed schedule), and
        ``pushes_ok`` — each LOGICAL write RPC counted once no matter how
        many retries it took, so with a fresh single-worker cluster it
        equals the servers' summed update counters EXACTLY (the
        no-double-apply accounting invariant ``hetu_tpu.chaos`` checks)."""
        out = np.zeros(wire.CLIENT_STATS_SLOTS, np.int64)
        self._lib.QueryClientStats(out.ctypes.data_as(_i64p),
                                   ctypes.c_int(wire.CLIENT_STATS_SLOTS))
        self._check()
        return wire.unpack_fields(wire.CLIENT_STATS_FIELDS, out)

    def SetWorldVersion(self, version):
        """hetu-elastic: stamp this worker's committed membership epoch
        onto every subsequent request. Servers armed via the coordinator's
        ``kSetWorldVersion`` reject a mismatched non-zero stamp (a
        straggler that missed a resize commit) as an error response; 0
        (the default) is unversioned legacy traffic, always accepted."""
        self._lib.SetWorldVersion(ctypes.c_ulonglong(int(version)))
        self._check()

    def GetWorldVersion(self) -> int:
        return int(self._lib.GetWorldVersion())

    def RefreshServers(self) -> int:
        """hetu-elastic: re-sync the native agent's server connections +
        key-range partitioner with the scheduler's address book after a
        committed resize. All in-flight traffic must be drained first
        (PSRuntime.drain() — the ElasticAgent handles the ordering).
        Returns the new server count."""
        n = self._lib.RefreshServers()
        self._check()
        if n < 0:
            raise RuntimeError("RefreshServers failed with no diagnostic")
        return n

    def SetCommQuant(self, mode):
        """hetuq: quantize this worker's PS value payloads on the wire
        (row-wise int8 + one f32 scale per row for sparse traffic, ~256-
        element blocks for dense — docs/COMM_QUANT.md). ``mode``: truthy /
        "int8" / "fp8" enables, falsy / "off" disables. The server always
        dequantizes and applies in f32, so dedup-sums, snapshots, and
        lost-update accounting are untouched."""
        on = mode not in (0, False, None, "", "off")
        self._lib.SetCommQuant(ctypes.c_int(1 if on else 0))
        self._check()

    # -- hetuchaos (docs/FAULT_TOLERANCE.md "Chaos testing") ----------------
    def SetPsCrc(self, on):
        """CRC32C payload checksums on this worker's PS traffic (default
        ON via ``HETU_PS_CRC``; 0 disables). Per-request negotiation
        (``kFlagCrc``) means the server verifies requests and checksums
        its responses only for CRC-speaking clients — so this one toggle
        A/Bs both legs live on the singleton worker."""
        self._lib.SetPsCrc(ctypes.c_int(1 if on else 0))
        self._check()

    def SetChaos(self, spec):
        """Arm a seeded chaos schedule on this worker's transport
        (``None``/``""`` disarms). Requires ``HETU_TEST_MODE`` — the same
        gate as every destructive hook. Spec grammar:
        ``hetu_tpu.chaos.parse_spec`` / csrc/ps/chaos.h. Every injected
        fault is logged to a bounded event ring (:meth:`DrainChaosEvents`)
        and decided purely from (seed, server, psf, tensor, sequence), so
        a failing schedule replays bit-identically from its seed."""
        s = (spec or "").encode()
        self._lib.SetChaos(s)
        self._check()

    def DrainChaosEvents(self, max_rows=65536) -> np.ndarray:
        """Drain injected-fault events across EVERY schedule armed this
        session, in arming order (oldest first within each) — a test that
        re-arms per phase still gets the full log on one drain at the
        end. Returns an (n, 6) int64 array with columns
        ``chaos.EVENT_COLS``: kind, server, psf, tensor, seq, arg. The
        array is a fresh copy (unlike the reused trail buffer) — chaos is
        a test-mode surface, not a hot path."""
        buf = np.zeros((int(max_rows), wire.CHAOS_EVENT_COLS), np.int64)
        n = self._lib.DrainChaosEvents(buf.ctypes.data_as(_i64p),
                                       ctypes.c_int(int(max_rows)))
        self._check()
        return buf[:max(0, int(n))].copy()

    # -- hetutrail (docs/OBSERVABILITY.md pillar 5) -------------------------
    def SetTrail(self, on):
        """Arm/disarm the native client-span ring at runtime (the env
        default is HETU_TRAIL_DIR at Init; A/Bs on one live worker need
        the explicit toggle, like SetCommQuant). Disarming clears it."""
        self._lib.SetTrail(ctypes.c_int(1 if on else 0))
        self._check()

    def SetTrailStep(self, step):
        """Stamp the current training step onto this worker's subsequent
        client RPC spans. The span context that crosses the wire stays the
        existing (client_id, req_id) pair — step rides only in the local
        span, so the wire format is unchanged."""
        self._lib.SetTrailStep(ctypes.c_longlong(int(step)))

    def DrainTrailSpans(self, max_rows=4096) -> np.ndarray:
        """Drain up to ``max_rows`` client RPC spans from the native ring
        (armed by HETU_TRAIL_DIR; always empty when off). Returns an
        (n, 10) int64 array with columns ``trail.CLIENT_COLS``:
        req_id, client_id, server, psf, tensor, step, t0_us (monotonic µs,
        comparable with server spans on the same host), dur_us, req_bytes,
        rsp_bytes. The array is a view of a REUSED buffer — consume it
        before the next drain."""
        buf = self._trail_buf
        if buf is None or buf.shape[0] < int(max_rows):
            buf = self._trail_buf = np.zeros(
                (int(max_rows), wire.TRAIL_COLS), np.int64)
        n = self._lib.DrainTrailSpans(buf.ctypes.data_as(_i64p),
                                      ctypes.c_int(int(max_rows)))
        self._check()
        return buf[:max(0, int(n))]

    def TrailDropped(self) -> int:
        """Spans dropped because the bounded client ring was full."""
        return int(self._lib.TrailDropped())

    def SnapshotNow(self, server: int, epoch: int = -1) -> dict:
        """hetusave coordinated-snapshot trigger: drive PS server
        ``server`` to write one epoch-stamped full-state snapshot NOW
        (synchronous — returns only after the snapshot dir is published
        and its ``LATEST_s<rank>`` pointer flipped, so the returned
        ``version`` is durable). The server must have been launched with
        ``DMLC_PS_SNAPSHOT_DIR``. Returns ``version`` (the per-server
        snapshot version the job manifest pins), ``counter`` (the update
        counter the snapshot covers), ``updates`` (the server's live
        counter at reply time — inside a quiesced drain window the two
        are EQUAL, the consistency proof hetusave checks), and the
        echoed ``epoch``. A production checkpoint primitive — not
        test-gated (docs/FAULT_TOLERANCE.md "Coordinated job
        snapshots")."""
        out = np.zeros(wire.SNAPSHOT_NOW_SLOTS, np.int64)
        self._lib.ServerSnapshotNow(ctypes.c_int(int(server)),
                                    ctypes.c_longlong(int(epoch)),
                                    out.ctypes.data_as(_i64p),
                                    ctypes.c_int(wire.SNAPSHOT_NOW_SLOTS))
        self._check()
        return wire.unpack_fields(wire.SNAPSHOT_NOW_FIELDS, out)

    def TestSlowApply(self, server=0, ms=100):
        """Test hook (requires HETU_TEST_MODE): delay PS server ``server``'s
        NEXT optimizer apply by ``ms`` — the deterministic slow leg the
        hetutrail critical-path and straggler tests attribute
        (``ps_slow@step[:ms]`` in HETU_FAULT_SPEC)."""
        self._lib.TestSlowApply(ctypes.c_int(int(server)),
                                ctypes.c_int(int(ms)))
        self._check()

    def TestCorruptNextQuant(self, node=-1):
        """Test hook (requires HETU_TEST_MODE): flip the scale bytes of the
        next quantized payload (``node`` < 0 = any tensor) — the server's
        length/scale validation must reject it as an error response."""
        self._lib.TestCorruptNextQuant(ctypes.c_int(int(node)))
        self._check()

    # -- tensor init (reference InitTensor binding) -------------------------
    def InitTensor(self, node, sparse, length, width, init_type, init_a,
                   init_b=1.0, seed=123, opt_type="sgd", lrs=(0.1,)):
        """sparse: 0/False = dense, 1/True = sparse 2D, 2 = cache table
        (versioned rows for bounded-staleness sync)."""
        if isinstance(init_type, str):
            init_type = _INIT_TYPE[init_type]
        if isinstance(opt_type, str):
            opt_type = _OPT_TYPE[opt_type]
        lrs_arr = np.asarray(lrs, dtype=np.float32)
        self._lib.InitTensor(
            ctypes.c_int(int(node)), ctypes.c_int(int(sparse)),
            ctypes.c_long(int(length)), ctypes.c_long(int(width)),
            ctypes.c_int(int(init_type)), ctypes.c_double(float(init_a)),
            ctypes.c_double(float(init_b)), ctypes.c_ulonglong(int(seed)),
            ctypes.c_int(int(opt_type)), lrs_arr.ctypes.data_as(_f32p),
            ctypes.c_int(len(lrs_arr)))
        self._check()

    def SetPushOpts(self, node, lr=-1.0, l2reg=0.0, weight_decay=0.0):
        """Attach per-step optimizer overrides [lr, l2reg, weight_decay] to
        this tensor's subsequent pushes — how lr schedules and l2/weight
        decay reach stateful SERVER-side optimizers (store.h UpdateOpts).
        lr < 0 with zero l2reg/weight_decay clears the override."""
        self._lib.SetPushOpts(ctypes.c_int(int(node)), ctypes.c_float(lr),
                              ctypes.c_float(l2reg),
                              ctypes.c_float(weight_decay))
        self._check()

    # -- dense --------------------------------------------------------------
    def Push(self, node, grad):
        g = _as_f32(grad)
        self._stage(node, g)
        self._lib.Push(ctypes.c_int(node), g.ctypes.data_as(_f32p),
                       ctypes.c_long(g.size))

    def Pull(self, node, out):
        """out: numpy array filled in place after Wait(node); returns it."""
        out = np.ascontiguousarray(out, dtype=np.float32)
        self._stage(node, out)
        self._lib.Pull(ctypes.c_int(node), out.ctypes.data_as(_f32p),
                       ctypes.c_long(out.size))
        return out

    def DDPushPull(self, node, grad, out):
        g = _as_f32(grad)
        out = np.ascontiguousarray(out, dtype=np.float32)
        self._stage(node, g)
        self._stage(node, out)
        self._lib.DDPushPull(ctypes.c_int(node), g.ctypes.data_as(_f32p),
                             out.ctypes.data_as(_f32p), ctypes.c_long(g.size))
        return out

    def Assign(self, node, value):
        """Raw overwrite (host-computed init values; bypasses the server's
        optimizer so Adam/Momentum slots never see the init as a grad)."""
        v = _as_f32(value)
        self._lib.AssignDense(ctypes.c_int(node), v.ctypes.data_as(_f32p),
                              ctypes.c_long(v.size))
        self._check()

    def SparseAssign(self, node, indices, values):
        idx, vals = _as_i64(indices).ravel(), _as_f32(values)
        self._lib.AssignRows(ctypes.c_int(node), idx.ctypes.data_as(_i64p),
                             vals.ctypes.data_as(_f32p),
                             ctypes.c_long(idx.size))
        self._check()

    # -- sparse -------------------------------------------------------------
    def SparsePush(self, node, indices, values):
        idx, vals = _as_i64(indices).ravel(), _as_f32(values)
        self._stage(node, idx)
        self._stage(node, vals)
        self._lib.SparsePush(ctypes.c_int(node), idx.ctypes.data_as(_i64p),
                             vals.ctypes.data_as(_f32p), ctypes.c_long(idx.size))

    def SparsePull(self, node, indices, out):
        idx = _as_i64(indices).ravel()
        out = np.ascontiguousarray(out, dtype=np.float32)
        self._stage(node, idx)
        self._stage(node, out)
        self._lib.SparsePull(ctypes.c_int(node), idx.ctypes.data_as(_i64p),
                             out.ctypes.data_as(_f32p), ctypes.c_long(idx.size))
        return out

    def SDPushPull(self, node, indices, values, out):
        idx, vals = _as_i64(indices).ravel(), _as_f32(values)
        out = np.ascontiguousarray(out, dtype=np.float32)
        self._stage(node, idx)
        self._stage(node, vals)
        self._stage(node, out)
        self._lib.SDPushPull(ctypes.c_int(node), idx.ctypes.data_as(_i64p),
                             vals.ctypes.data_as(_f32p), ctypes.c_long(idx.size),
                             out.ctypes.data_as(_f32p))
        return out

    def SSPushPull(self, node, in_indices, values, out_indices, out):
        iidx, vals = _as_i64(in_indices).ravel(), _as_f32(values)
        oidx = _as_i64(out_indices).ravel()
        assert iidx.size == oidx.size
        out = np.ascontiguousarray(out, dtype=np.float32)
        self._stage(node, iidx)
        self._stage(node, vals)
        self._stage(node, oidx)
        self._stage(node, out)
        self._lib.SSPushPull(ctypes.c_int(node), iidx.ctypes.data_as(_i64p),
                             vals.ctypes.data_as(_f32p),
                             oidx.ctypes.data_as(_i64p),
                             out.ctypes.data_as(_f32p), ctypes.c_long(iidx.size))
        return out

    # -- data blobs (reference PushData/PullData) ---------------------------
    def PushData(self, node, ids, values, lens):
        ids = np.ascontiguousarray(ids, dtype=np.uint64)
        vals = _as_f32(values)
        lens = np.ascontiguousarray(lens, dtype=np.int64)
        q = self._lib.PushData(ctypes.c_int(node), ids.ctypes.data_as(_u64p),
                               ctypes.c_int(ids.size),
                               vals.ctypes.data_as(_f32p),
                               lens.ctypes.data_as(_i64p))
        self._stage(-q - 1, (ids, vals, lens))
        return q

    def PullData(self, node, ids, out, lens):
        ids = np.ascontiguousarray(ids, dtype=np.uint64)
        out = np.ascontiguousarray(out, dtype=np.float32)
        lens = np.ascontiguousarray(lens, dtype=np.int64)
        q = self._lib.PullData(ctypes.c_int(node), ids.ctypes.data_as(_u64p),
                               ctypes.c_int(ids.size),
                               out.ctypes.data_as(_f32p),
                               lens.ctypes.data_as(_i64p))
        self._stage(-q - 1, (ids, out, lens))
        return q, out

    def WaitData(self, query):
        self._lib.WaitData(ctypes.c_long(query))
        self._staging.pop(-query - 1, None)
        self._check()

    # -- control ------------------------------------------------------------
    def Wait(self, node):
        if hasattr(node, "value"):
            node = node.value
        self._lib.Wait(ctypes.c_int(int(node)))
        self._staging.pop(int(node), None)
        self._check()

    def BarrierWorker(self):
        self._lib.BarrierWorker()
        self._check()

    def Clear(self, node):
        self._lib.Clear(ctypes.c_int(node))
        self._check()

    def ClearOnServer(self, node):
        self._lib.ClearOnServer(ctypes.c_int(node))
        self._check()

    def SaveParam(self, node, directory):
        os.makedirs(directory, exist_ok=True)
        self._lib.SaveParam(ctypes.c_int(node), str(directory).encode())
        self._check()

    def LoadParam(self, node, directory):
        self._lib.LoadParam(ctypes.c_int(node), str(directory).encode())
        self._check()

    def startRecord(self, directory):
        os.makedirs(directory, exist_ok=True)
        self._lib.startRecord(str(directory).encode())
        # every guard()-wrapped entry point must be checked at its call
        # site: an unchecked stashed error would otherwise surface as a
        # bogus "Finalize failed" when close() reads LastError at teardown
        self._check()

    def getLoads(self):
        import json
        raw = self._lib.getLoads().decode()
        self._check()
        return json.loads(raw)
