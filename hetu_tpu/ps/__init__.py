"""Parameter-server subsystem (reference ``ps-lite`` + ``src/hetu_cache``).

Host-resident KV server with server-side optimizers for sparse embeddings,
plus the bounded-staleness embedding cache. The C++ core lives in
``hetu_tpu/csrc``; this package holds the Python client/launch surface.

Milestone status: scaffolding — the server/client land in a later commit this
round (SURVEY.md §7 step 5).
"""
from __future__ import annotations

import weakref

_NOT_READY = ("The parameter-server backend is not initialized. Launch roles "
              "via hetu_tpu.launcher (scheduler/server/worker) first.")

_worker = None
_runtimes: "weakref.WeakSet" = weakref.WeakSet()


def _register_runtime(rt):
    """PSRuntimes register here so worker_finish can drain them."""
    _runtimes.add(rt)


def scheduler_init():
    from .server import start_scheduler_from_env
    start_scheduler_from_env()


def scheduler_finish():
    from .server import stop_scheduler
    stop_scheduler()


def server_init():
    from .server import start_server_from_env
    start_server_from_env()


def server_finish():
    from .server import stop_server
    stop_server()


def _register_worker(worker):
    global _worker
    _worker = worker


def worker_init():
    from .client import PSClient
    PSClient.from_env()  # registers itself via _register_worker


def worker_finish():
    global _worker
    # drain every live PSRuntime's async I/O streams BEFORE Finalize closes
    # the sockets: an in-flight prefetch/push racing the teardown can leave a
    # pool thread blocked in recv and hang the whole process on pool join
    for rt in list(_runtimes):
        try:
            rt.drain()
            rt.shutdown()
        except Exception:  # noqa: BLE001 — teardown must not throw
            pass
    if _worker is not None:
        # clear the global FIRST: a raise must not leave a half-finalized
        # agent registered where a retrying caller would re-Finalize it
        w, _worker = _worker, None
        # log-and-continue: this is the launcher's `finally:` path — a
        # teardown-window socket error must not turn a successful worker
        # run into a nonzero exit (and a restart-budget hit)
        w.close(raise_on_error=False)


def get_worker_communicate():
    assert _worker is not None, _NOT_READY
    return _worker
