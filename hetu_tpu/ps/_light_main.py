"""Standalone PS scheduler/server process body — run by FILE PATH, never
imported.

A PS scheduler or server is a pure C++ TCP loop (csrc/ps/{scheduler,
server}.h); it needs ctypes and nothing else. Spawning it as a
``multiprocessing`` child of a module inside the package made every
cluster bootstrap pay the full ``hetu_tpu`` import (jax + flax, seconds
per process) just to reach a ``CDLL(...).Init()``. Executing THIS file
directly (``python .../_light_main.py``) sidesteps the package import
entirely: cluster startup is interpreter start + dlopen.

Contract (mirrors hetu_tpu/ps/server.py's role entry points exactly):
- env ``HETU_PS_LIB``: path to the built libhetu_ps.so (the parent has
  already run csrc/build.py's build()).
- env ``DMLC_ROLE``: "scheduler" (Init → SchedulerWait → Finalize) or
  "server" (Init → StartServer implicit via role → poll
  ``HETU_PS_STOPFILE`` → Finalize). Topology comes from the same DMLC_*
  env vars the reference uses (runner.py:186-190).

Reference parity: the reference launches these roles as separate python
processes through its own binding too (gpu_ops/executor.py:80-100); this
file is that launcher minus the framework import.
"""
import ctypes
import os
import sys
import time


def main():
    lib = ctypes.CDLL(os.environ["HETU_PS_LIB"])
    lib.LastError.restype = ctypes.c_char_p
    lib.Init()
    err = lib.LastError()
    if err:
        raise RuntimeError(err.decode())
    role = os.environ["DMLC_ROLE"]
    if role == "scheduler":
        lib.SchedulerWait()
        # bounded teardown wait (DMLC_PS_SCHED_WAIT_TIMEOUT_MS): on timeout
        # the native side reports which ranks never checked out — exit
        # nonzero with that diagnostic instead of hanging forever
        err = lib.LastError()
        lib.Finalize()
        if err:
            print(f"[hetu ps scheduler] {err.decode()}", file=sys.stderr)
            return 1
    elif role == "server":
        lib.StartServer()
        err = lib.LastError()
        if err:
            raise RuntimeError(err.decode())
        stopfile = os.environ["HETU_PS_STOPFILE"]
        while not os.path.exists(stopfile):
            time.sleep(0.05)
        lib.Finalize()
    else:
        raise SystemExit(f"unsupported role for light main: {role!r}")


if __name__ == "__main__":
    sys.exit(main())
