"""The ONE Python mirror of the PS wire protocol laid down in csrc/ps/*.h.

Every Python-side unpacker of a C++ reply (elastic coordinator, PSClient
ctypes stats, supervisor probes, recovery manifest checks, chaos event
drains) imports its slot counts, field names, header structs, flags and
enum values from here — never a per-file magic number. ``bin/hetucheck``
(docs/ANALYSIS.md, Tier D) parses the C++ headers and asserts this module
matches them slot-for-slot, so drift between the languages fails CI
instead of corrupting an unpack at 3am.

Layout provenance (file:symbol of the C++ truth):

- ``MSG_HDR`` / ``ARG_HDR``     — net.h ``MsgHeader`` / ``ArgHeader``
- ``PSF``                       — net.h ``enum class PsfType``
- ``AT_*``                      — net.h ``enum class ArgType``
- ``FLAG_*`` / ``QUANT_WIRE_BLOCK`` — net.h wire flag constants
- ``SERVER_STATS_FIELDS``       — server.h kServerStats reply
- ``SNAPSHOT_NOW_FIELDS``       — server.h kSnapshotNow reply
- ``LIST_PARAMS_FIELDS``        — server.h kListParams row stride
- ``CLIENT_STATS_FIELDS``       — worker.h ``client_stats()``
- ``TRAIL_SPAN_FIELDS``         — worker.h ``kTrailCols`` drain rows
- ``RESIZE_STATE_FIELDS``       — scheduler.h kResizeState reply
- ``WORLD_REPLY_FIELDS``        — scheduler.h ``world_reply_locked``
- ``CHAOS_EVENT_FIELDS`` / ``CHAOS_KINDS`` — chaos.h ``ChaosEngine``
- ``SHARD_MAGIC_V2`` / ``SHARD_META_LEN`` / ``OPT_SLOT_COUNTS``
                                — server.h v2 shard format + store.h OptType

jax-free on purpose: hetucheck imports this under plain CPython in CI.
"""
from __future__ import annotations

import struct

# --------------------------------------------------------------------------
# Message framing — net.h MsgHeader / ArgHeader. ArgHeader's middle i32 is
# the documented field-reuse slot: ``pad`` on the wire, carrying the CRC32C
# of the arg bytes when the message's FLAG_CRC is set. MsgHeader's last i32
# was ``pad`` pre-elastic and is now ``world_ver`` (membership epoch stamp);
# the wire layout never changed, only the meaning of the slot.
MSG_HDR = struct.Struct("<iiQiiii")   # 32 bytes
MSG_HDR_FIELDS = ("type", "tensor_id", "req_id", "n_args", "flags",
                  "client_id", "world_ver")
ARG_HDR = struct.Struct("<iiQ")       # 16 bytes
ARG_HDR_FIELDS = ("dtype", "crc_or_pad", "nbytes")

# net.h wire flags + quantization block
FLAG_QUANT_RSP = 1      # kFlagQuantRsp: response values may ride kQI8
FLAG_CRC = 2            # kFlagCrc: per-arg CRC32C in ArgHeader.pad
QUANT_WIRE_BLOCK = 256  # kQuantWireBlock: dense int8 scale granularity

# net.h enum class ArgType
AT_F32, AT_I64, AT_F64, AT_BYTES, AT_I32, AT_U64, AT_QI8 = range(7)

# --------------------------------------------------------------------------
# net.h enum class PsfType — the full request vocabulary. hetucheck diffs
# this dict against the parsed enum, so adding a PSF in C++ without
# mirroring it here is a CI failure (and vice versa).
PSF = {
    "kRegister": 0, "kAddressBook": 1, "kBarrier": 2, "kShutdown": 3,
    "kAck": 4, "kHeartbeat": 5, "kQueryServers": 6, "kServerStats": 7,
    "kDensePush": 10, "kDensePull": 11, "kDDPushPull": 12,
    "kSparsePush": 20, "kSparsePull": 21, "kSDPushPull": 22,
    "kSSPushPull": 23,
    "kParamInit": 30, "kParamClear": 31, "kParamSave": 32, "kParamLoad": 33,
    "kParamAssign": 34, "kParamAssignRows": 35,
    "kSyncEmbedding": 40, "kPushEmbedding": 41, "kPushSyncEmbedding": 42,
    "kDataPush": 50, "kDataPull": 51,
    "kProposeResize": 60, "kResizeState": 61, "kCommitResize": 62,
    "kFinishResize": 63, "kResizeLog": 64, "kListParams": 65,
    "kSetWorldVersion": 66, "kSnapshotNow": 67,
    "kTestSlowApply": 70,
}

# The ids Python-side coordinators put on the wire themselves (elastic.py,
# supervisor.py speak raw sockets; everything else goes through ctypes).
K_QUERY_SERVERS = PSF["kQueryServers"]
K_SERVER_STATS = PSF["kServerStats"]
K_PARAM_SAVE = PSF["kParamSave"]
K_PARAM_LOAD = PSF["kParamLoad"]
K_PROPOSE_RESIZE = PSF["kProposeResize"]
K_RESIZE_STATE = PSF["kResizeState"]
K_COMMIT_RESIZE = PSF["kCommitResize"]
K_FINISH_RESIZE = PSF["kFinishResize"]
K_RESIZE_LOG = PSF["kResizeLog"]
K_LIST_PARAMS = PSF["kListParams"]
K_SET_WORLD_VERSION = PSF["kSetWorldVersion"]
K_SNAPSHOT_NOW = PSF["kSnapshotNow"]

# --------------------------------------------------------------------------
# Reply slot layouts. The tuples are in C++ slot order; ``len(...)`` is the
# count to request/unpack. Unpack helpers below build the canonical dicts so
# consumers key on names, never indices.

# server.h kServerStats: int64_t stats[11]
SERVER_STATS_FIELDS = (
    "updates",            # 0 optimizer updates applied
    "snapshot_updates",   # 1 updates covered by the latest snapshot
    "restored_updates",   # 2 counter restored from (-1 = fresh start)
    "snapshot_version",   # 3 latest published snapshot version
    "n_params",           # 4 live param count
    "requests",           # 5 requests served
    "apply_ns",           # 6 total apply wall ns (writes only)
    "apply_count",        # 7 apply sample count
    "snapshot_age_ms",    # 8 ms since last snapshot (-1 = none yet)
    "dedup_clients",      # 9 dedup-ledger occupancy
    "crc_rejects",        # 10 CRC-rejected requests
)
SERVER_STATS_SLOTS = len(SERVER_STATS_FIELDS)

# worker.h client_stats(): 10 relaxed counters in declaration order
CLIENT_STATS_FIELDS = (
    "rpcs",               # 0 rpc_count_
    "retries",            # 1 retry_count_
    "failovers",          # 2 failover_count_
    "quant_raw_bytes",    # 3 val_raw_bytes_
    "quant_wire_bytes",   # 4 val_wire_bytes_
    "timeouts",           # 5 timeout_count_
    "backoff_ms",         # 6 backoff_ms_total_
    "crc_rejects",        # 7 crc_reject_count_
    "chaos_faults",       # 8 chaos_faults()
    "pushes_ok",          # 9 push_ok_count_
)
CLIENT_STATS_SLOTS = len(CLIENT_STATS_FIELDS)

# server.h kSnapshotNow reply: int64_t out[4]
SNAPSHOT_NOW_FIELDS = ("version", "counter", "updates", "epoch")
SNAPSHOT_NOW_SLOTS = len(SNAPSHOT_NOW_FIELDS)

# server.h kListParams: flat i64 rows, one per stored param
LIST_PARAMS_FIELDS = ("key", "kind", "size", "width", "otype")
LIST_PARAMS_STRIDE = len(LIST_PARAMS_FIELDS)

# scheduler.h kResizeState: int64_t vals[13] (slots 10-12 are suffix
# extensions — slot 10 hetusave, 11-12 hetupilot — older clients reading
# a shorter prefix stay valid)
RESIZE_STATE_FIELDS = (
    "world_version", "pending_version", "num_workers", "num_servers",
    "pending_nw", "pending_ns", "drained", "survivors",
    "new_servers_ready", "members", "snapshot_epochs",
    "pilot_commit_epochs", "pilot_rollback_epochs",
)
RESIZE_STATE_SLOTS = len(RESIZE_STATE_FIELDS)

# scheduler.h kFinishResize second i32 (the actuation tag): WHY an
# identity-resize barrier era was run, so the kResizeState era counters
# attribute each era to its cause. 0/absent = a plain resize or an
# untagged abort (counted nowhere); "snapshot" = a hetusave coordinated
# epoch (counts snapshot_epochs); "pilot_commit"/"pilot_rollback" = a
# hetupilot actuation verdict (counts pilot_*_epochs). The legacy bool
# (snapshot=True) is tag 1 — exactly-once epoch counting is unchanged.
ACTUATION_TAGS = {
    "none": 0, "snapshot": 1, "pilot_commit": 2, "pilot_rollback": 3,
}

# scheduler.h world_reply_locked: int64_t vals[5]
WORLD_REPLY_FIELDS = ("world_version", "num_workers", "num_servers",
                      "dp_rank", "start_step")
WORLD_REPLY_SLOTS = len(WORLD_REPLY_FIELDS)

# worker.h kTrailCols drain rows (DrainTrailSpans)
TRAIL_SPAN_FIELDS = ("req_id", "client_id", "server", "psf", "tensor",
                     "step", "t0_us", "dur_us", "req_bytes", "rsp_bytes")
TRAIL_COLS = len(TRAIL_SPAN_FIELDS)

# chaos.h ChaosEngine: drain() row layout + enum class ChaosKind
CHAOS_EVENT_FIELDS = ("kind", "server", "psf", "tensor", "seq", "arg")
CHAOS_EVENT_COLS = len(CHAOS_EVENT_FIELDS)
CHAOS_KINDS = {
    "kNone": 0, "kDrop": 1, "kDelay": 2, "kDup": 3, "kReorder": 4,
    "kCorrupt": 5, "kPartition": 6, "kDropRsp": 7,
}

# --------------------------------------------------------------------------
# server.h v2 full-state shard format + store.h OptType slot counts (how
# many auxiliary state tensors each server-side optimizer persists).
SHARD_MAGIC_V2 = -2
SHARD_META_LEN = 8
# store.h enum class OptType + how many aux tensors alloc_slots() gives each
OPT_TYPES = {"kSGD": 0, "kMomentum": 1, "kNesterov": 2, "kAdaGrad": 3,
             "kAdam": 4}
OPT_SLOT_COUNTS = {0: 0,   # kSGD      (stateless)
                   1: 1,   # kMomentum (accum)
                   2: 1,   # kNesterov (accum)
                   3: 1,   # kAdaGrad  (accum)
                   4: 2}   # kAdam     (accum, accum2)


def unpack_fields(fields, vals) -> dict:
    """Zip a C++ i64 reply into its canonical dict. Tolerates a longer
    reply (suffix extensions, e.g. kResizeState slot 10) but never a
    shorter one — a short reply is exactly the drift this module exists
    to catch, so fail loudly at the unpack site."""
    vals = list(vals)
    if len(vals) < len(fields):
        raise ValueError(
            f"wire reply has {len(vals)} slot(s), expected at least "
            f"{len(fields)}: {fields} — C++/Python slot-layout drift? "
            "(run bin/hetucheck)")
    return {name: int(vals[i]) for i, name in enumerate(fields)}
